#!/usr/bin/env python3
"""Cross-framework serving A/B: our pipeline vs a plain for-loop.

The inference-frameworks benchmark (arXiv 2210.04323) makes its points
with one discipline: the *same model* under the *same open-loop trace*
across serving stacks. This tool is that comparison for us, with the
no-framework end of the spectrum as the baseline — the plain Python
``for`` loop every serving script starts life as:

- **baseline**: requests replayed at their pre-drawn arrival times; a
  single loop pops each one and runs the exact same element objects
  (normalize → model filter) synchronously, blocking on the device
  result before touching the next request. No scheduler, no async
  dispatch, no compiled windows — and no framework overhead either.
- **ours**: the same elements linked into a Pipeline under
  PipelineRunner defaults (async dispatch, chain fusion, the compiled
  steady-state loop), fed the identical arrival trace through AppSrc,
  completions stamped per-pts at a TensorSink callback after a device
  sync.

Same model, same preprocessing code, same trace — the delta is purely
what the runtime adds (overhead) and what it recovers (pipelining +
the scheduler bypass). Reported in bench.py's ``host_path`` family as
``cross_framework``; never gated — it's a comparison point, not an
invariant.

Run directly (``python tools/serving_baseline.py [--json]``) or import
``run_ab()``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: same normalize option as bench.py's label config
NORMALIZE_OPT = "typecast:float32,add:-127.5,div:127.5"


def _stages(small: bool):
    """The two compute elements both arms share, plus the input frame.
    `small` swaps in the width-0.35 / 32px zoo variant so the A/B runs
    in seconds on CPU emulation; on an accelerator run it full-size."""
    from nnstreamer_tpu.elements import TensorFilter, TensorTransform

    if small:
        shape, model = (1, 32, 32, 3), \
            "zoo://mobilenet_v2?width=0.35&input_size=32"
    else:
        shape, model = (1, 224, 224, 3), "zoo://mobilenet_v2"
    norm = TensorTransform(name="n", mode="arithmetic",
                           option=NORMALIZE_OPT)
    filt = TensorFilter(name="f", model=model)
    frame = np.random.default_rng(0).integers(0, 256, shape, np.uint8)
    return [norm, filt], frame, shape


def _percentile(v, p):
    if not v:
        return 0.0
    s = sorted(v)
    return s[min(len(s) - 1, int(len(s) * p / 100))]


def _report(lats_ms, n, elapsed):
    return {
        "completed": len(lats_ms),
        "offered": n,
        "throughput_rps": round(len(lats_ms) / elapsed, 2)
        if elapsed else 0.0,
        "p50_ms": round(_percentile(lats_ms, 50), 2),
        "p99_ms": round(_percentile(lats_ms, 99), 2),
    }


def run_baseline(arrivals, *, small: bool = True) -> dict:
    """Plain for-loop serving: pop each request at (or after) its
    arrival time, run the stages synchronously, block on the device
    result. Latency is arrival→done — a request that queues behind a
    slow predecessor pays that wait, exactly as the naive script
    would make it pay."""
    import jax

    stages, frame, shape = _stages(small)
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    # the same negotiation walk the runner does — it's what opens the
    # filter's backend
    spec = TensorsSpec.of(TensorInfo(shape, DType.UINT8))
    for e in stages:
        spec = e.negotiate([spec])[0]
    for e in stages:
        e.start()
    try:
        # warm/compile outside the clock, like every arm in bench.py
        buf = TensorBuffer.of(frame, pts=-1)
        for e in stages:
            buf = e.process(0, buf)[0][1]
        jax.block_until_ready(tuple(buf.tensors))

        lats = []
        t0 = time.perf_counter()
        for i, t_arr in enumerate(arrivals):
            now = time.perf_counter() - t0
            if now < t_arr:
                time.sleep(t_arr - now)
            buf = TensorBuffer.of(frame, pts=i)
            for e in stages:
                buf = e.process(0, buf)[0][1]
            jax.block_until_ready(tuple(buf.tensors))
            lats.append((time.perf_counter() - t0 - t_arr) * 1e3)
        elapsed = time.perf_counter() - t0
    finally:
        for e in stages:
            e.stop()
    return _report(lats, len(arrivals), elapsed)


def run_ours(arrivals, *, small: bool = True) -> dict:
    """The same stages under the runtime: AppSrc → normalize → filter →
    TensorSink, PipelineRunner defaults (compiled steady-state loop
    included). Frames pushed at the identical arrival times; the sink
    callback blocks on the device result and stamps completion."""
    import jax

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements.sinks import TensorSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    stages, frame, shape = _stages(small)
    pipe = nns.Pipeline("serving_ab")
    src = AppSrc(spec=TensorsSpec.of(TensorInfo(shape, DType.UINT8)),
                 name="src")
    done: dict = {}
    done_lock = threading.Lock()
    all_done = threading.Event()
    n = len(arrivals)
    t0_box = [0.0]
    recv = [0]                 # every emission, warmup included

    def _on_data(buf):
        jax.block_until_ready(tuple(buf.tensors))
        with done_lock:
            recv[0] += 1
            if buf.pts >= 0:
                done[buf.pts] = time.perf_counter() - t0_box[0]
                if len(done) >= n:
                    all_done.set()

    sink = TensorSink(name="sink", new_data=_on_data)
    chain = [src] + stages + [sink]
    for e in chain:
        pipe.add(e)
    for a, b in zip(chain, chain[1:]):
        pipe.link(a, b)
    runner = nns.PipelineRunner(pipe, queue_capacity=max(16, n)).start()
    try:
        # warmup/compile outside the clock (pts=-1 frames don't count).
        # Bursts, not a trickle: the compiled steady-state loop jits
        # one scan per pow2 window size, and those buckets must be warm
        # before the trace starts — same discipline as bench.py's
        # prewarm (the arms compare serving, not compile luck).
        pushed = 0

        def _burst(sz):
            nonlocal pushed
            for _ in range(sz):
                src.push(TensorBuffer.of(frame, pts=-1))
            pushed += sz
            t_wait = time.perf_counter()
            while recv[0] < pushed:
                if time.perf_counter() - t_wait > 120:
                    raise RuntimeError("warmup stalled")
                time.sleep(0.002)

        # which pow2 window a burst lands in depends on thread timing,
        # so fixed bursts leave buckets cold nondeterministically —
        # keep probing until the filter backend stops compiling
        be = stages[-1].backend

        def _cc():
            # window-scan traces count separately from per-frame bucket
            # traces; warmup must outlast BOTH kinds of compile
            return (be.compile_count
                    + getattr(be, "window_compile_count", 0))

        compiles = -1
        for _ in range(8):
            if be is not None and _cc() == compiles:
                break
            compiles = _cc() if be is not None else -1
            for sz in (16, 7, 5, 3):
                _burst(sz)

        t0 = t0_box[0] = time.perf_counter()
        for i, t_arr in enumerate(arrivals):
            now = time.perf_counter() - t0
            if now < t_arr:
                time.sleep(t_arr - now)
            src.push(TensorBuffer.of(frame, pts=i))
        if not all_done.wait(timeout=300):
            raise RuntimeError(
                f"drain stalled: {len(done)}/{n} completions")
        elapsed = time.perf_counter() - t0
    finally:
        runner.stop()
    lats = [(done[i] - arrivals[i]) * 1e3 for i in range(n) if i in done]
    return _report(lats, n, elapsed)


def run_ab(n: int = 64, rate_hz: float = 0.0, *,
           small: bool = True, seed: int = 0) -> dict:
    """Both arms over one pre-drawn Poisson trace. rate_hz=0 picks a
    rate near the baseline's own capacity (measured on 8 probe frames)
    so the comparison sits at the knee, where a serving stack's
    pipelining actually matters — an idle trace would just measure two
    ways of being idle."""
    if rate_hz <= 0:
        probe = run_baseline(np.zeros(8), small=small)
        rate_hz = max(1.0, 0.8 * probe["throughput_rps"])
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, size=n))
    out = {"n": n, "rate_hz": round(float(rate_hz), 2), "seed": seed,
           "model": _stages(small)[0][1].props["model"],
           "baseline": run_baseline(arrivals, small=small),
           "ours": run_ours(arrivals, small=small)}
    b, o = out["baseline"], out["ours"]
    out["throughput_ratio"] = (round(
        o["throughput_rps"] / b["throughput_rps"], 2)
        if b["throughput_rps"] else 0.0)
    out["p99_ratio"] = (round(b["p99_ms"] / o["p99_ms"], 2)
                        if o["p99_ms"] else 0.0)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--rate-hz", type=float, default=0.0)
    ap.add_argument("--full-size", action="store_true",
                    help="full 224px mobilenet_v2 (accelerator runs)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    out = run_ab(n=args.n, rate_hz=args.rate_hz,
                 small=not args.full_size)
    if args.json:
        print(json.dumps(out, indent=1))
    else:
        b, o = out["baseline"], out["ours"]
        print(f"trace: n={out['n']} poisson {out['rate_hz']} rps "
              f"model={out['model']}")
        print(f"baseline (for-loop): {b['throughput_rps']} rps  "
              f"p50 {b['p50_ms']} ms  p99 {b['p99_ms']} ms")
        print(f"ours (pipeline):     {o['throughput_rps']} rps  "
              f"p50 {o['p50_ms']} ms  p99 {o['p99_ms']} ms")
        print(f"throughput ratio {out['throughput_ratio']}x, "
              f"p99 ratio {out['p99_ratio']}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
