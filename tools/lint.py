#!/usr/bin/env python3
"""Dependency-free static gate (SURVEY.md §5.2 parity — the reference
runs Coverity/format gates in CI; this is the in-repo analog, ast-based
so it needs nothing beyond the stdlib).

Checks per file:
  - parses (syntax gate)
  - unused imports (noqa-respecting)
  - bare `except:` clauses
  - mutable default arguments (list/dict/set literals)
  - tabs in indentation

Exit 0 clean, 1 with findings. Usage: python tools/lint.py [paths...]
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

DEFAULT_PATHS = ["nnstreamer_tpu", "tests", "tools", "bench.py",
                 "__graft_entry__.py"]


class Visitor(ast.NodeVisitor):
    def __init__(self, src_lines):
        self.lines = src_lines
        self.imports = {}      # name → (lineno, stated name)
        self.used = set()
        self.findings = []

    def _noqa(self, lineno: int) -> bool:
        line = self.lines[lineno - 1] if lineno - 1 < len(self.lines) else ""
        return "noqa" in line

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            if not self._noqa(node.lineno):
                self.imports[name] = (node.lineno, a.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            name = a.asname or a.name
            if not self._noqa(node.lineno):
                self.imports[name] = (node.lineno, a.name)
        self.generic_visit(node)

    def visit_Name(self, node):
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        # pkg.mod.attr marks pkg used
        n = node
        while isinstance(n, ast.Attribute):
            n = n.value
        if isinstance(n, ast.Name):
            self.used.add(n.id)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.type is None and not self._noqa(node.lineno):
            self.findings.append((node.lineno, "bare `except:` clause"))
        self.generic_visit(node)

    def _check_defaults(self, node):
        for d in node.args.defaults + node.args.kw_defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self.findings.append(
                    (d.lineno, "mutable default argument"))

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)


def lint_file(path: Path) -> list:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    v = Visitor(lines)
    v.visit(tree)
    findings = v.findings
    # string annotations / docstring references count as usage signals
    blob = src
    for name, (lineno, stated) in sorted(v.imports.items()):
        if name in v.used:
            continue
        if f"__all__" in blob and f'"{name}"' in blob:
            continue
        # string-typed annotations ("TensorsSpec") or doctest mentions
        uses = blob.count(name)
        if uses <= 1:
            findings.append((lineno, f"unused import: {stated}"))
    for i, line in enumerate(lines, 1):
        stripped = line[:len(line) - len(line.lstrip())]
        if "\t" in stripped:
            findings.append((i, "tab in indentation"))
    return sorted(findings)


def main(argv) -> int:
    paths = argv or DEFAULT_PATHS
    files = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            files += sorted(pp.rglob("*.py"))
        elif pp.suffix == ".py":
            files.append(pp)
    bad = 0
    for f in files:
        if "_pb2" in f.name:   # generated code plays by its own rules
            continue
        for lineno, msg in lint_file(f):
            print(f"{f}:{lineno}: {msg}")
            bad += 1
    if bad:
        print(f"\n{bad} finding(s)")
        return 1
    print(f"lint clean: {len(files)} files")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
