#!/usr/bin/env python3
"""Plugin scaffolding generator — dev-tooling parity with the reference's
tools/development/nnstreamerCodeGenCustomFilter.py, re-aimed at this
framework's in-process registration model.

Usage:
    python tools/new_plugin.py decoder my_mode [outdir]
    python tools/new_plugin.py converter my_format [outdir]
    python tools/new_plugin.py filter my_model [outdir]
    python tools/new_plugin.py element my_element [outdir]

Emits a runnable skeleton that registers itself on import; drop the file
on the pipeline's python path (or a `plugin_paths` dir from the config)
and reference it from a launch line.
"""

from __future__ import annotations

import sys
from pathlib import Path

DECODER = '''"""tensor_decoder mode={name} — generated skeleton."""

from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import OctetSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec


@register_decoder("{name}")
class {cls}(DecoderSubplugin):
    def init(self, props: dict) -> None:
        self.option1 = props.get("option1", "")

    def negotiate(self, in_spec: TensorsSpec):
        # validate the tensor input; declare the output stream type
        return OctetSpec(rate=in_spec.rate)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        # tensors → media payload
        return buf
'''

CONVERTER = '''"""tensor_converter mode=custom:{name} — generated skeleton."""

from nnstreamer_tpu.elements.converter import ConverterSubplugin, register_converter
from nnstreamer_tpu.graph.media import MediaSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec


@register_converter("{name}")
class {cls}(ConverterSubplugin):
    def negotiate(self, in_spec: MediaSpec) -> TensorsSpec:
        # declare the tensor stream produced from the media input
        return TensorsSpec(tensors=(), format=TensorFormat.FLEXIBLE,
                           rate=in_spec.rate)

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        # media payload → tensors
        return buf
'''

FILTER = '''"""tensor_filter framework=custom model={name} — generated skeleton."""

from nnstreamer_tpu.backends.custom import register_custom_easy


def {name}(tensors):
    """tuple of arrays in → tuple of arrays out (jnp ops run on TPU)."""
    return tensors


register_custom_easy("{name}", {name})
'''

ELEMENT = '''"""{name} pipeline element — generated skeleton."""

from typing import List, Sequence

from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import (
    Element, Emission, PropDef, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer


@register_element("{name}")
class {cls}(Element):
    ELEMENT_NAME = "{name}"
    PROPS = {{
        "option": PropDef(str, "", "example property"),
    }}

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        # validate input specs; declare one output spec per src pad
        return [in_specs[0]]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        # transform/route the buffer; return (src_pad, buffer) emissions
        return [(0, buf)]
'''

KINDS = {"decoder": DECODER, "converter": CONVERTER, "filter": FILTER,
         "element": ELEMENT}


def main(argv) -> int:
    if len(argv) < 2 or argv[0] not in KINDS:
        print(__doc__)
        return 2
    kind, name = argv[0], argv[1]
    import keyword

    if not name.isidentifier() or keyword.iskeyword(name):
        print(f"plugin name {name!r} must be a valid non-keyword "
              f"identifier")
        return 2
    outdir = Path(argv[2]) if len(argv) > 2 else Path(".")
    outdir.mkdir(parents=True, exist_ok=True)
    cls = "".join(w.capitalize() for w in name.split("_"))
    path = outdir / f"{name}_{kind}.py"
    if path.exists():
        print(f"{path} already exists; not overwriting")
        return 1
    path.write_text(KINDS[kind].format(name=name, cls=cls))
    print(f"wrote {path} — import it (or add its dir to plugin_paths) "
          f"to register {kind} {name!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
