#!/usr/bin/env bash
# Full local CI gate (SURVEY.md §5.2/Lx parity: the reference runs meson
# builds + ninja test + ssat + static analysis in CI; this is the whole
# equivalent pipeline in one script).
#
# Usage: tools/ci.sh [--fast]   (--fast skips the pytest suite)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== native build =="
make -C native

echo "== static gate (lint + bytecode compile) =="
python tools/lint.py
python -m compileall -q nnstreamer_tpu tests tools bench.py __graft_entry__.py

echo "== generated docs up to date =="
JAX_PLATFORMS=cpu python tools/gen_docs.py --check

echo "== single-chip compile check (__graft_entry__.entry) =="
python - <<'EOF'
import __graft_entry__ as g
import jax
fn, args = g.entry()
jax.eval_shape(fn, *args)   # traces the flagship model without devices
print("entry() traces clean")
EOF

echo "== multichip dryrun (virtual 8-device mesh) =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

if [[ "${1:-}" != "--fast" ]]; then
  echo "== test suite =="
  python -m pytest tests/ -x -q
fi

echo "CI gate passed"
