#!/usr/bin/env python3
"""Host-path microbenchmark: wakeup latency + per-hop overhead.

Pure CPU, no model, no accelerator — this measures the *scheduler*, the
part of `piped_fps` no kernel work can recover (BENCH host-path tax):

- **wakeup latency**: push→render time of a single frame through an
  otherwise idle `appsrc → fakesink` pipeline. The old timeout-poll
  scheduler slept in ``q.get(timeout=0.1)``, so an idle hop could cost
  up to 100 ms; the condition-variable channel (runtime/channel.py)
  wakes the consumer on enqueue — this number should sit far below the
  old poll floor.
- **per-hop overhead**: open-loop frames through
  ``appsrc → N× passthrough tensor_transform → fakesink``, fused
  (chain fusion on: the transforms share one worker thread) vs unfused
  (one thread + channel per element), reported as µs/frame and
  µs/frame/hop.

Run directly (``python tools/profile_hostpath.py [--json]``) or import
the ``measure_*`` functions — bench.py's ``host_path`` family and the
tier-1 smoke test in tests/test_hostpath.py reuse them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: the old scheduler's get/put poll tick — the latency floor this
#: overhaul removes; kept as the reference line in reports and tests
OLD_POLL_FLOOR_MS = 100.0


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            int(round(p / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[k]


def _frame():
    import numpy as np

    return np.zeros((1, 64), np.float32)


class _EventSink:
    """fakesink that timestamps each render and sets an event — lets
    the wakeup measurement block on the actual render instant instead
    of polling a counter (polling would floor the measurement at the
    poll interval, the very artifact being measured)."""

    def __new__(cls, name=None):
        from nnstreamer_tpu.graph.pipeline import SinkElement

        class _Impl(SinkElement):
            ELEMENT_NAME = "event_sink"

            def __init__(self, name=None):
                super().__init__(name=name)
                self.count = 0
                self.t_render = 0.0
                self.evt = threading.Event()

            def render(self, buf):
                self.t_render = time.perf_counter()
                self.count += 1
                self.evt.set()

        return _Impl(name=name)


def build_passthrough(n_transforms: int, sink_cls=None):
    """appsrc → n_transforms× identity tensor_transform → fakesink.

    Every transform is arithmetic add:0.0 — negligible compute, so the
    measured time is almost entirely scheduler hop overhead."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import FakeSink, TensorTransform
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    pipe = nns.Pipeline("hostpath")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 64), DType.FLOAT32)), name="src")
    stages = [src]
    for i in range(n_transforms):
        stages.append(TensorTransform(name=f"t{i}", mode="arithmetic",
                                      option="add:0.0"))
    sink = (sink_cls or FakeSink)(name="sink")
    stages.append(sink)
    for e in stages:
        pipe.add(e)
    for a, b in zip(stages, stages[1:]):
        pipe.link(a, b)
    return pipe, src, sink


def measure_wakeup_latency(n: int = 200, warmup: int = 20) -> dict:
    """Closed-loop push→render latency (ms) on an idle pipeline —
    the enqueue→dequeue wakeup cost, twice (appsrc pump + sink hop)."""
    from nnstreamer_tpu.runtime.scheduler import PipelineRunner
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    pipe, src, sink = build_passthrough(0, sink_cls=_EventSink)
    runner = PipelineRunner(pipe, optimize=False).start()
    frame = _frame()
    lats = []
    try:
        for i in range(warmup + n):
            sink.evt.clear()
            t0 = time.perf_counter()
            src.push(TensorBuffer.of(frame, pts=i))
            if not sink.evt.wait(10.0):
                raise RuntimeError(
                    f"wakeup measurement stalled at frame {i} "
                    f"(sink at {sink.count})")
            if i >= warmup:
                lats.append((sink.t_render - t0) * 1e3)
        src.end()
        runner.wait(30)
    finally:
        runner.stop()
    lats.sort()
    return {
        "n": n,
        "p50_ms": round(_percentile(lats, 50), 4),
        "p95_ms": round(_percentile(lats, 95), 4),
        "max_ms": round(lats[-1], 4),
        "old_poll_floor_ms": OLD_POLL_FLOOR_MS,
    }


def measure_hop_overhead(n_transforms: int = 4, n_frames: int = 2000,
                         fused: bool = True, repeats: int = 3) -> dict:
    """Open-loop per-frame host cost through a passthrough chain.

    Best-of-`repeats` (scheduler noise is one-sided: interference only
    ever adds time). `fused=False` pins chain_fusion off so the same
    graph runs one thread + channel per element — the A/B the host_path
    bench family reports."""
    from nnstreamer_tpu.runtime.scheduler import PipelineRunner
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    hops = n_transforms + 1            # link count src→…→sink
    best_us = float("inf")
    for _ in range(repeats):
        pipe, src, sink = build_passthrough(n_transforms)
        runner = PipelineRunner(pipe, optimize=False,
                                chain_fusion=fused).start()
        frame = _frame()
        pts = 0
        try:
            for _ in range(64):        # warm the path
                src.push(TensorBuffer.of(frame, pts=pts))
                pts += 1
            while sink.count < 64:
                time.sleep(0.0002)
            t0 = time.perf_counter()
            for _ in range(n_frames):
                src.push(TensorBuffer.of(frame, pts=pts))
                pts += 1
            target = 64 + n_frames
            while sink.count < target:
                if runner._error is not None:
                    raise RuntimeError(
                        f"pipeline failed: {runner._error}")
                time.sleep(0.0002)
            dt = time.perf_counter() - t0
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        best_us = min(best_us, dt / n_frames * 1e6)
    return {
        "transforms": n_transforms,
        "hops": hops,
        "frames": n_frames,
        "fused": bool(fused),
        "per_frame_us": round(best_us, 2),
        "per_hop_us": round(best_us / hops, 2),
    }


def profile(n_frames: int = 2000, n_wakeup: int = 200) -> dict:
    """The full host-path picture (what `host_path` in bench.py ships)."""
    fused = measure_hop_overhead(4, n_frames, fused=True)
    unfused = measure_hop_overhead(4, n_frames, fused=False)
    speedup = (unfused["per_frame_us"] / fused["per_frame_us"]
               if fused["per_frame_us"] else 0.0)
    return {
        "wakeup_latency": measure_wakeup_latency(n_wakeup),
        "hop_overhead": {
            "fused": fused,
            "unfused": unfused,
            "fused_speedup": round(speedup, 2),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--frames", type=int, default=2000,
                    help="open-loop frames per hop-overhead run")
    ap.add_argument("--wakeups", type=int, default=200,
                    help="samples for the wakeup-latency measurement")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    args = ap.parse_args(argv)
    res = profile(args.frames, args.wakeups)
    if args.json:
        print(json.dumps(res, indent=2))
        return 0
    w = res["wakeup_latency"]
    print(f"wakeup latency (push→render, idle pipeline, n={w['n']}):")
    print(f"  p50 {w['p50_ms']:.3f} ms   p95 {w['p95_ms']:.3f} ms   "
          f"max {w['max_ms']:.3f} ms   (old poll floor: "
          f"{w['old_poll_floor_ms']:.0f} ms)")
    h = res["hop_overhead"]
    for label in ("fused", "unfused"):
        r = h[label]
        print(f"{label:>8}: {r['per_frame_us']:8.1f} µs/frame over "
              f"{r['hops']} hops ({r['per_hop_us']:.1f} µs/hop)")
    print(f"chain-fusion speedup: {h['fused_speedup']:.2f}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
