#!/usr/bin/env python3
"""Direct entry for the project linter — same as
`python -m nnstreamer_tpu lint` (see docs/static_analysis.md).

Kept runnable from a clean checkout with no install: adds the repo
root to sys.path, then delegates.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from nnstreamer_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
